"""Client-server proxy mode: one endpoint multiplexing isolated drivers.

Parity with the reference's proxier
(``python/ray/util/client/server/proxier.py``): a single public ``ray://``
endpoint accepts many clients and gives each its own *dedicated backend
driver process* (a ``ray_tpu.util.client.server`` instance with its own
runtime), so tenants cannot see each other's objects, actors, or crashes —
the reference's ``SpecificServer``-per-client design.

The proxy itself never parses client traffic: after pairing a connection
with a backend it splices bytes in both directions (works for both the
Python pickle-frame protocol and the C++ binary protocol, which the backend
sniffs itself).  A small warm pool hides the backend's runtime-start
latency; exited backends are reaped and respawned on demand.

Run standalone::

    python -m ray_tpu.util.client.proxier --port 10001 --num-cpus 4
"""

from __future__ import annotations

import socket
import subprocess
import sys
import threading
import time
from typing import List, Optional


class _Backend:
    """One dedicated driver process serving exactly one client at a time."""

    def __init__(self, num_cpus: Optional[int], extra_args: Optional[List[str]] = None):
        # backend picks its own free port and prints it; --port 0 delegates
        # the choice to the OS
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            self.port = probe.getsockname()[1]
        cmd = [
            sys.executable, "-m", "ray_tpu.util.client.server",
            "--host", "127.0.0.1", "--port", str(self.port),
        ]
        if num_cpus is not None:
            cmd += ["--num-cpus", str(num_cpus)]
        cmd += extra_args or []
        self.proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True
        )
        self._ready = threading.Event()
        threading.Thread(target=self._watch_ready, name="proxy-backend-ready", daemon=True).start()

    def _watch_ready(self) -> None:
        for line in self.proc.stdout:  # server prints its listen line once up
            if "listening on" in line:
                self._ready.set()
        # keep draining so the pipe never fills
        self._ready.set()

    def wait_ready(self, timeout: float = 120.0) -> bool:
        ok = self._ready.wait(timeout)
        return ok and self.proc.poll() is None

    def connect(self) -> socket.socket:
        return socket.create_connection(("127.0.0.1", self.port), timeout=10)

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()


class ProxyServer:
    """Accepts clients, pairs each with a dedicated backend, splices bytes."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 10001,
        num_cpus_per_backend: Optional[int] = None,
        warm_backends: int = 1,
    ):
        self._num_cpus = num_cpus_per_backend
        self._sock = socket.create_server((host, port))
        self.host, self.port = self._sock.getsockname()[:2]
        self.address = f"{self.host}:{self.port}"
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._warm: List[_Backend] = []
        self._active: List[_Backend] = []
        self._warm_target = max(0, warm_backends)
        for _ in range(self._warm_target):
            self._warm.append(_Backend(self._num_cpus))
        self._thread = threading.Thread(target=self._accept_loop, name="rt-proxy", daemon=True)

    def start(self) -> "ProxyServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            backends = self._warm + self._active
            self._warm, self._active = [], []
        for b in backends:
            b.kill()

    # ------------------------------------------------------------------
    def _take_backend(self) -> _Backend:
        with self._lock:
            while self._warm:
                b = self._warm.pop()
                if b.alive:
                    break
                b.kill()
            else:
                b = _Backend(self._num_cpus)
            self._active.append(b)
        # refill the warm pool off-thread so the next client doesn't pay
        # the runtime-start latency either
        def refill():
            with self._lock:
                deficit = self._warm_target - len(self._warm)
            for _ in range(max(0, deficit)):
                nb = _Backend(self._num_cpus)
                with self._lock:
                    if self._stop.is_set():
                        nb.kill()
                        return
                    self._warm.append(nb)

        threading.Thread(target=refill, name="proxy-refill", daemon=True).start()
        return b

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name=f"proxy-conn-{addr[1]}",
            ).start()

    def _serve_conn(self, client: socket.socket) -> None:
        backend = self._take_backend()
        try:
            if not backend.wait_ready():
                client.close()
                return
            upstream = backend.connect()
        except OSError:
            client.close()
            return

        def pump(src: socket.socket, dst: socket.socket) -> None:
            try:
                while True:
                    data = src.recv(1 << 16)
                    if not data:
                        break
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                for s in (src, dst):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

        t1 = threading.Thread(target=pump, args=(client, upstream), daemon=True)
        t2 = threading.Thread(target=pump, args=(upstream, client), daemon=True)
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        for s in (client, upstream):
            try:
                s.close()
            except OSError:
                pass
        # session over: the tenant's driver dies with it (full isolation —
        # reference proxier reaps SpecificServers on disconnect the same way)
        backend.kill()
        with self._lock:
            try:
                self._active.remove(backend)
            except ValueError:
                pass


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description="ray_tpu client proxy (multi-tenant)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=10001)
    parser.add_argument("--num-cpus", type=int, default=None, help="CPUs per tenant backend")
    parser.add_argument("--warm", type=int, default=1, help="prestarted warm backends")
    args = parser.parse_args(argv)

    proxy = ProxyServer(args.host, args.port, args.num_cpus, warm_backends=args.warm).start()
    print(f"ray_tpu client proxy listening on {proxy.address}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        proxy.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
