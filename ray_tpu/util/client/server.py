"""Thin-client server: hosts real driver state for remote clients.

Parity with ``python/ray/util/client/server/server.py:96``
(``RayletServicer``): the server owns real ``ObjectRef``s / actor handles on
behalf of each connected client session and executes the client's
put/get/task/actor RPCs against the in-process runtime. Each connection is a
session; its refs are released on disconnect (the reference ties object
lifetime to client_id the same way).

Run standalone::

    python -m ray_tpu.util.client.server --port 10001 --num-cpus 8
"""

from __future__ import annotations

import logging
import socket
import threading
import uuid
from typing import Any, Dict

from ray_tpu.util.client.binary import BINARY_MAGIC, recv_exact as _recv_exact_raw, serve_binary
from ray_tpu.util.client.common import ActorMarker, RefMarker, recv_msg, send_msg, translate

logger = logging.getLogger(__name__)


class _Session:
    def __init__(self):
        self.refs: Dict[bytes, Any] = {}        # ref_id -> ObjectRef
        self.actors: Dict[bytes, Any] = {}      # actor_id -> ActorHandle
        self.fn_cache: Dict[bytes, Any] = {}    # fn hash -> deserialized callable
        self.lock = threading.Lock()


class ClientServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 10001):
        import ray_tpu as rt

        if not rt.is_initialized():
            raise RuntimeError("ray_tpu must be initialized before serving clients")
        self._rt = rt
        self._sock = socket.create_server((host, port))
        self.host, self.port = self._sock.getsockname()[:2]
        self.address = f"{self.host}:{self.port}"
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, name="rt-client-server", daemon=True)

    def start(self) -> "ClientServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn, addr), daemon=True,
                name=f"rt-client-{addr[1]}",
            ).start()

    def _serve_conn(self, conn: socket.socket, addr) -> None:
        session = _Session()
        send_lock = threading.Lock()
        try:
            # Mode sniff: C++/native clients open with the 8-byte magic
            # "RTCPBIN1" (cross-language frontend, reference cpp/ parity);
            # Python clients open with a frame-length header (first byte 0
            # for any sane frame size).
            first8 = _recv_exact_raw(conn, 8)
            if first8 == BINARY_MAGIC:
                self._serve_binary(conn, session)
                return
            while not self._stop.is_set():
                msg = recv_msg(conn, preread_header=first8)
                first8 = None
                # each request handled on its own thread so a blocking get
                # doesn't starve concurrent calls (gRPC-stream parity)
                threading.Thread(
                    target=self._handle, args=(conn, send_lock, session, msg), daemon=True
                ).start()
        except (ConnectionError, OSError):
            pass
        finally:
            with session.lock:
                session.refs.clear()
                session.actors.clear()
            try:
                conn.close()
            except OSError:
                pass

    def _serve_binary(self, conn: socket.socket, session: _Session) -> None:
        serve_binary(self._rt, session, conn, stop_event=self._stop)

    def _handle(self, conn, send_lock, session: _Session, msg: dict) -> None:
        rid = msg.get("rid")
        try:
            result = self._dispatch(session, msg)
            reply = {"rid": rid, "ok": True, "result": result}
        except BaseException as exc:  # noqa: BLE001 — errors cross the wire
            reply = {"rid": rid, "ok": False, "error": exc}
        try:
            with send_lock:
                send_msg(conn, reply)
        except (ConnectionError, OSError):
            pass

    # ------------------------------------------------------------------
    def _resolve(self, session: _Session, obj):
        def ref_fn(marker: RefMarker):
            with session.lock:
                return session.refs[marker.id]

        def actor_fn(marker: ActorMarker):
            with session.lock:
                return session.actors[marker.id]

        return translate(obj, ref_fn, actor_fn)

    def _register_ref(self, session: _Session, ref) -> bytes:
        ref_id = uuid.uuid4().bytes
        with session.lock:
            session.refs[ref_id] = ref
        return ref_id

    def _dispatch(self, session: _Session, msg: dict):
        rt = self._rt
        op = msg["op"]
        if op == "put":
            return self._register_ref(session, rt.put(msg["value"]))
        if op == "get":
            with session.lock:
                refs = [session.refs[i] for i in msg["ref_ids"]]
            values = rt.get(refs, timeout=msg.get("timeout"))
            return values
        if op == "task":
            fn = session.fn_cache.get(msg["fn_hash"])
            if fn is None:
                import cloudpickle

                fn = cloudpickle.loads(msg["fn"])
                session.fn_cache[msg["fn_hash"]] = fn
            args = self._resolve(session, msg["args"])
            kwargs = self._resolve(session, msg["kwargs"])
            remote_fn = rt.remote(fn) if not msg.get("options") else rt.remote(fn).options(**msg["options"])
            out = remote_fn.remote(*args, **kwargs)
            if isinstance(out, list):
                return [self._register_ref(session, r) for r in out]
            return self._register_ref(session, out)
        if op == "create_actor":
            import cloudpickle

            cls = session.fn_cache.get(msg["fn_hash"])
            if cls is None:
                cls = cloudpickle.loads(msg["cls"])
                session.fn_cache[msg["fn_hash"]] = cls
            args = self._resolve(session, msg["args"])
            kwargs = self._resolve(session, msg["kwargs"])
            actor_cls = rt.remote(cls) if not msg.get("options") else rt.remote(cls).options(**msg["options"])
            handle = actor_cls.remote(*args, **kwargs)
            actor_id = uuid.uuid4().bytes
            with session.lock:
                session.actors[actor_id] = handle
            return {"actor_id": actor_id, "methods": [m for m in dir(handle) if not m.startswith("_")]}
        if op == "actor_call":
            with session.lock:
                handle = session.actors[msg["actor_id"]]
            args = self._resolve(session, msg["args"])
            kwargs = self._resolve(session, msg["kwargs"])
            method = getattr(handle, msg["method"])
            return self._register_ref(session, method.remote(*args, **kwargs))
        if op == "wait":
            with session.lock:
                refs = [session.refs[i] for i in msg["ref_ids"]]
            by_ref = {id(r): i for r, i in zip(refs, msg["ref_ids"])}
            ready, not_ready = rt.wait(
                refs, num_returns=msg["num_returns"], timeout=msg.get("timeout")
            )
            return ([by_ref[id(r)] for r in ready], [by_ref[id(r)] for r in not_ready])
        if op == "kill_actor":
            with session.lock:
                handle = session.actors.get(msg["actor_id"])
            if handle is not None:
                rt.kill(handle, no_restart=msg.get("no_restart", True))
            return None
        if op == "release":
            with session.lock:
                for i in msg["ref_ids"]:
                    session.refs.pop(i, None)
            return None
        if op == "cluster_info":
            return {
                "cluster_resources": rt.cluster_resources(),
                "available_resources": rt.available_resources(),
                "nodes": rt.nodes(),
            }
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown client op: {op!r}")


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description="ray_tpu thin-client server")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=10001)
    parser.add_argument("--num-cpus", type=int, default=None)
    args = parser.parse_args(argv)

    import ray_tpu as rt

    rt.init(num_cpus=args.num_cpus)
    server = ClientServer(args.host, args.port).start()
    print(f"ray_tpu client server listening on {server.address}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        rt.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
