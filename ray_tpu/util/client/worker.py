"""Thin client: the user-side API over a client-server connection.

Parity with ``python/ray/util/client/`` (``ClientObjectRef`` in
``common.py``, the ``ray.util.connect`` entry): ``connect("host:port")``
returns a :class:`ClientContext` exposing remote/get/put/wait/kill with the
same call shapes as the in-process API, but every operation executes in the
server's runtime. A background reader thread multiplexes responses to
concurrent callers by request id.
"""

from __future__ import annotations

import hashlib
import socket
import threading
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Union

import cloudpickle

from ray_tpu.util.client.common import ActorMarker, RefMarker, recv_msg, send_msg


class ClientObjectRef:
    __slots__ = ("_id", "_ctx", "__weakref__")

    def __init__(self, ref_id: bytes, ctx: "ClientContext"):
        self._id = ref_id
        self._ctx = ctx

    def id(self) -> bytes:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def __repr__(self):
        return f"ClientObjectRef({self._id.hex()[:16]})"

    def __reduce__(self):  # only markers cross the wire
        raise TypeError("ClientObjectRef cannot be pickled; pass it in task args instead")

    def __del__(self):
        ctx = self._ctx
        if ctx is not None and not ctx._closed:
            ctx._release(self._id)


class ClientRemoteFunction:
    def __init__(self, ctx: "ClientContext", fn, options: Optional[dict] = None):
        self._ctx = ctx
        self._fn = fn
        self._fn_bytes = cloudpickle.dumps(fn)
        self._fn_hash = hashlib.sha1(self._fn_bytes).digest()
        self._options = options or {}

    def options(self, **new_options) -> "ClientRemoteFunction":
        return ClientRemoteFunction(self._ctx, self._fn, {**self._options, **new_options})

    def remote(self, *args, **kwargs):
        out = self._ctx._call(
            op="task",
            fn=self._fn_bytes,
            fn_hash=self._fn_hash,
            args=self._ctx._encode(args),
            kwargs=self._ctx._encode(kwargs),
            options=self._options,
        )
        if isinstance(out, list):
            return [ClientObjectRef(i, self._ctx) for i in out]
        return ClientObjectRef(out, self._ctx)


class ClientActorMethod:
    def __init__(self, handle: "ClientActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        ctx = self._handle._ctx
        ref_id = ctx._call(
            op="actor_call",
            actor_id=self._handle._id,
            method=self._name,
            args=ctx._encode(args),
            kwargs=ctx._encode(kwargs),
        )
        return ClientObjectRef(ref_id, ctx)


class ClientActorHandle:
    def __init__(self, ctx: "ClientContext", actor_id: bytes, methods: List[str]):
        self._ctx = ctx
        self._id = actor_id
        self._methods = set(methods)

    def __getattr__(self, name: str) -> ClientActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ClientActorMethod(self, name)


class ClientActorClass:
    def __init__(self, ctx: "ClientContext", cls, options: Optional[dict] = None):
        self._ctx = ctx
        self._cls = cls
        self._cls_bytes = cloudpickle.dumps(cls)
        self._fn_hash = hashlib.sha1(self._cls_bytes).digest()
        self._options = options or {}

    def options(self, **new_options) -> "ClientActorClass":
        return ClientActorClass(self._ctx, self._cls, {**self._options, **new_options})

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        out = self._ctx._call(
            op="create_actor",
            cls=self._cls_bytes,
            fn_hash=self._fn_hash,
            args=self._ctx._encode(args),
            kwargs=self._ctx._encode(kwargs),
            options=self._options,
        )
        return ClientActorHandle(self._ctx, out["actor_id"], out["methods"])


class ClientContext:
    """The connected session (``ray.util.client.RayAPIStub`` parity)."""

    def __init__(self, address: str, connect_timeout: float = 10.0):
        host, _, port = address.rpartition(":")
        self._sock = socket.create_connection((host or "127.0.0.1", int(port)), timeout=connect_timeout)
        self._sock.settimeout(None)
        self._send_lock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._pending_lock = threading.Lock()
        self._rid = 0
        self._closed = False
        self._released: List[bytes] = []
        self._reader = threading.Thread(target=self._read_loop, name="rt-client-reader", daemon=True)
        self._reader.start()
        assert self._call(op="ping") == "pong"

    # ------------------------------------------------------------------
    def _read_loop(self) -> None:
        try:
            while not self._closed:
                reply = recv_msg(self._sock)
                with self._pending_lock:
                    fut = self._pending.pop(reply["rid"], None)
                if fut is None:
                    continue
                if reply["ok"]:
                    fut.set_result(reply["result"])
                else:
                    fut.set_exception(reply["error"])
        except (ConnectionError, OSError) as exc:
            with self._pending_lock:
                pending, self._pending = self._pending, {}
            for fut in pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError(f"client connection lost: {exc}"))

    def _call(self, **msg) -> Any:
        if self._closed:
            raise ConnectionError("client context is disconnected")
        fut: Future = Future()
        with self._pending_lock:
            self._rid += 1
            rid = self._rid
            self._pending[rid] = fut
        msg["rid"] = rid
        with self._send_lock:
            send_msg(self._sock, msg)
        return fut.result()

    def _release(self, ref_id: bytes) -> None:
        # batched, fire-and-forget distributed GC
        self._released.append(ref_id)
        if len(self._released) >= 32:
            batch, self._released = self._released, []
            try:
                with self._pending_lock:
                    self._rid += 1
                    rid = self._rid
                    self._pending[rid] = Future()  # reply discarded by reader
                with self._send_lock:
                    send_msg(self._sock, {"rid": rid, "op": "release", "ref_ids": batch})
            except (ConnectionError, OSError):
                pass

    def _encode(self, obj):
        """Swap ClientObjectRef/ClientActorHandle for wire markers."""
        if isinstance(obj, ClientObjectRef):
            return RefMarker(obj._id)
        if isinstance(obj, ClientActorHandle):
            return ActorMarker(obj._id)
        if isinstance(obj, (list, tuple)):
            return type(obj)(self._encode(x) for x in obj)
        if isinstance(obj, dict):
            return {k: self._encode(v) for k, v in obj.items()}
        return obj

    # ------------------------------------------------------------------ API
    def remote(self, fn_or_class=None, **options):
        if fn_or_class is None:
            return lambda f: self.remote(f, **options)
        if isinstance(fn_or_class, type):
            return ClientActorClass(self, fn_or_class, options or None)
        return ClientRemoteFunction(self, fn_or_class, options or None)

    def put(self, value: Any) -> ClientObjectRef:
        return ClientObjectRef(self._call(op="put", value=value), self)

    def get(self, refs: Union[ClientObjectRef, Sequence[ClientObjectRef]], *, timeout: Optional[float] = None):
        single = isinstance(refs, ClientObjectRef)
        ref_list = [refs] if single else list(refs)
        values = self._call(op="get", ref_ids=[r._id for r in ref_list], timeout=timeout)
        return values[0] if single else values

    def wait(self, refs: Sequence[ClientObjectRef], *, num_returns: int = 1, timeout: Optional[float] = None):
        by_id = {r._id: r for r in refs}
        ready_ids, not_ready_ids = self._call(
            op="wait", ref_ids=[r._id for r in refs], num_returns=num_returns, timeout=timeout
        )
        return [by_id[i] for i in ready_ids], [by_id[i] for i in not_ready_ids]

    def kill(self, actor: ClientActorHandle, *, no_restart: bool = True) -> None:
        self._call(op="kill_actor", actor_id=actor._id, no_restart=no_restart)

    def cluster_resources(self) -> dict:
        return self._call(op="cluster_info")["cluster_resources"]

    def available_resources(self) -> dict:
        return self._call(op="cluster_info")["available_resources"]

    def nodes(self) -> list:
        return self._call(op="cluster_info")["nodes"]

    def disconnect(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.disconnect()


def connect(address: str, **kw) -> ClientContext:
    """Connect to a :class:`~ray_tpu.util.client.server.ClientServer`
    (``ray.util.connect`` parity; address form ``"host:port"`` or
    ``"ray://host:port"``)."""
    if address.startswith("ray://"):
        address = address[len("ray://"):]
    ctx = ClientContext(address, **kw)
    connect._last_context = ctx  # ray.util.disconnect() closes the latest
    return ctx
