"""User-facing placement-group API.

Parity: ``ray.util.placement_group`` / ``remove_placement_group`` /
``placement_group_table`` (``python/ray/util/placement_group.py``) — the
convenience layer over the control service's PG manager, returning a
handle usable with ``PlacementGroupSchedulingStrategy``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core.ids import PlacementGroupID
from ray_tpu.core.resources import ResourceSet
from ray_tpu.runtime.placement import PlacementGroupInfo, PlacementStrategy


class PlacementGroup:
    """Handle over a created group (parity: util PlacementGroup)."""

    def __init__(self, info: PlacementGroupInfo):
        self._info = info

    @property
    def id(self) -> PlacementGroupID:
        return self._info.pg_id

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return [b.to_dict() for b in self._info.bundles]

    def ready(self):
        """ObjectRef resolving True once the group is scheduled — a PENDING
        group (awaiting capacity) blocks until the manager's retry places
        it (parity: PlacementGroup.ready())."""
        import ray_tpu

        info = self._info

        def _ready() -> bool:
            import time

            from ray_tpu.runtime.placement import PlacementGroupState

            while info.state is not PlacementGroupState.CREATED:
                if info.state is PlacementGroupState.REMOVED:
                    raise RuntimeError("placement group was removed before it was placed")
                time.sleep(0.05)
            return True

        return ray_tpu.remote(_ready).options(execution="thread").remote()

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        """Block up to timeout_seconds for the group to be scheduled."""
        import time

        from ray_tpu.runtime.placement import PlacementGroupState

        deadline = time.monotonic() + timeout_seconds
        while time.monotonic() < deadline:
            if self._info.state is PlacementGroupState.CREATED:
                return True
            if self._info.state is PlacementGroupState.REMOVED:
                return False
            time.sleep(0.02)
        return self._info.state is PlacementGroupState.CREATED


def get_placement_group(name: str) -> PlacementGroup:
    """Look up a live placement group by name (parity:
    util.get_placement_group)."""
    from ray_tpu.api import get_cluster

    cluster = get_cluster()
    for info in cluster.control.placement_groups.list_groups():
        if info.name == name and info.state.name != "REMOVED":
            return PlacementGroup(info)
    raise ValueError(f"no placement group named {name!r}")


def get_current_placement_group() -> Optional[PlacementGroup]:
    """The placement group the CURRENT actor was scheduled under, or None
    (parity: util.get_current_placement_group).  Resolved from the actor's
    creation spec — available for in-process/thread execution, where the
    cluster state is reachable; process workers see None."""
    from ray_tpu.api import get_cluster
    from ray_tpu.runtime.context import task_context
    from ray_tpu.runtime.scheduler import PlacementGroupSchedulingStrategy

    current = task_context.current()
    if current is None:
        return None
    task_id, _node = current
    try:
        cluster = get_cluster()
    except Exception:  # noqa: BLE001 — no in-proc cluster (process worker)
        return None
    # actor tasks embed their ActorID: the creation spec carries the
    # scheduling strategy the actor was placed with
    actor_id = task_id.actor_id()
    if actor_id.is_nil():
        return None
    spec = getattr(cluster, "_actor_specs", {}).get(actor_id)
    strategy = getattr(spec, "scheduling_strategy", None)
    if strategy is None:
        return None
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        pg = strategy.placement_group
        if isinstance(pg, PlacementGroup):
            return pg
        info = cluster.control.placement_groups.get(getattr(pg, "id", pg))
        return PlacementGroup(info) if info is not None else None
    return None


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
    labels: Optional[Dict[str, str]] = None,
    pack_by_label: Optional[str] = None,
) -> PlacementGroup:
    """Create (and synchronously schedule) a placement group.

    ``labels`` restricts candidate nodes to those carrying every (k, v);
    ``pack_by_label`` places the whole gang on nodes sharing ONE value of
    that label — e.g. ``pack_by_label="ray_tpu.io/slice-id"`` with
    ``strategy="STRICT_SPREAD"`` gang-places one bundle per host of a
    single TPU slice (reference: TPU pod affinity via the
    ``TPU-<pod>-head`` resource, accelerators/tpu.py:13-33)."""
    import ray_tpu
    from ray_tpu.runtime.worker import global_worker

    if not bundles:
        raise ValueError("placement group bundles cannot be empty")
    try:
        PlacementStrategy[strategy]
    except KeyError:
        valid = [s.name for s in PlacementStrategy]
        raise ValueError(f"invalid placement strategy {strategy!r}; valid: {valid}")
    if lifetime not in (None, "detached"):
        raise ValueError(f"lifetime must be None or 'detached', got {lifetime!r}")
    # lifetime="detached" is accepted for API parity; in-process groups are
    # process-scoped either way (no cross-driver registry to detach into)

    worker = global_worker()
    info = PlacementGroupInfo(
        PlacementGroupID.of(worker.job_id),
        [ResourceSet(b) for b in bundles],
        PlacementStrategy[strategy],
        name=name,
        labels=labels,
        pack_by_label=pack_by_label,
    )
    cluster = ray_tpu.get_cluster()
    # create() registers the group either way; an infeasible one stays
    # PENDING and is retried when capacity joins (autoscaler parity)
    cluster.control.placement_groups.create(info)
    return PlacementGroup(info)


def remove_placement_group(pg: PlacementGroup) -> None:
    import ray_tpu

    ray_tpu.get_cluster().control.placement_groups.remove(pg.id)


def placement_group_table(pg: Optional[PlacementGroup] = None) -> Dict:
    import ray_tpu

    mgr = ray_tpu.get_cluster().control.placement_groups
    rows = {}
    for info in mgr.list_groups():
        with mgr._lock:   # remove() clears bundle_placements under this lock
            placements = dict(info.bundle_placements)
            state = info.state.name
        rows[info.pg_id.hex()] = {
            "name": info.name,
            "strategy": info.strategy.name,
            "state": state,
            "bundles": [b.to_dict() for b in info.bundles],
            "bundle_placements": {i: nid.hex() for i, nid in placements.items()},
        }
    if pg is not None:
        return rows.get(pg.id.hex(), {})
    return rows
