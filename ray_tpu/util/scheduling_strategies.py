"""Scheduling strategies (public module path parity with
``python/ray/util/scheduling_strategies.py:15,41,135``)."""

from ray_tpu.runtime.scheduler import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "PlacementGroupSchedulingStrategy",
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
]
