"""joblib backend running Parallel() jobs as remote tasks.

Parity: ``python/ray/util/joblib/`` — ``register_ray_tpu()`` then
``joblib.parallel_backend("ray_tpu")`` routes scikit-learn style
``Parallel(n_jobs=...)`` work through the task fabric. Implements the
modern joblib backend contract: ``submit`` returns a
``concurrent.futures.Future`` resolved by a waiter thread per in-flight
batch (joblib batches aggressively, so waiter count stays ~n_jobs).
"""

from __future__ import annotations

from concurrent.futures import Future


def register_ray_tpu() -> None:
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray_tpu", _make_backend())


def _make_backend():
    from joblib._parallel_backends import ParallelBackendBase

    class RayTpuBackend(ParallelBackendBase):
        """Each joblib BatchedCalls runs as one remote task."""

        supports_retrieve_callback = True
        supports_timeout = True

        def effective_n_jobs(self, n_jobs):
            import ray_tpu as rt

            if not rt.is_initialized():
                rt.init()
            if n_jobs == 1:
                return 1
            cpus = max(int(rt.cluster_resources().get("CPU", 1)), 1)
            if n_jobs is None:
                n_jobs = -1
            if n_jobs < 0:
                # joblib idiom: -1 = all cpus, -2 = all but one, ...
                return max(cpus + 1 + n_jobs, 1)
            return min(n_jobs, cpus)

        def configure(self, n_jobs=1, parallel=None, **backend_kwargs):
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

        def submit(self, func, callback=None):
            import threading

            import ray_tpu as rt

            ref = rt.remote(lambda: func()).remote()
            future: Future = Future()

            def waiter():
                try:
                    future.set_result(rt.get(ref))
                except BaseException as exc:  # noqa: BLE001 — joblib re-raises
                    future.set_exception(exc)

            threading.Thread(target=waiter, daemon=True).start()
            if callback is not None:
                future.add_done_callback(callback)
            return future

        def retrieve_result_callback(self, future):
            return future.result()

        def terminate(self):
            pass

        def abort_everything(self, ensure_ready=True):
            if ensure_ready and self.parallel is not None:
                self.configure(n_jobs=self.parallel.n_jobs, parallel=self.parallel)

    return RayTpuBackend
