"""Recovery invariants checked after a chaos run reaches quiescence.

The point of deterministic chaos is not that faults fired — it is that the
fabric's recovery machinery provably restored every contract afterwards.
These are the contracts (ISSUE 2 tentpole):

  1. **No stuck work**: the task manager's pending set drains to empty.
  2. **No silent object loss**: every workload ref resolves within a bound —
     to a value, or by *raising* a typed error (``ObjectLostError``,
     ``RayTaskError``, ``ActorDiedError``, ...).  A get that hangs, or that
     *returns* an ``ObjectLostError`` instance as if it were data, is a
     violation.
  3. **Terminal exactly once**: the task-event store shows exactly one
     terminal record (FINISHED/FAILED) per ``(task_id, attempt)`` — a task
     that double-commits (or whose retry resurrects a completed attempt)
     is a correctness bug even when every get succeeds.
  4. **Refcounts at baseline**: once the workload's refs are dropped, the
     reference counter returns to its pre-run footprint — recovery must not
     leak pins.
  5. **Retries are visible**: every terminal record with ``attempt = n > 0``
     has matching distinct ``retry::`` spans in the span store (PR 1
     tracing), so a reproduced schedule can be audited from the timeline.

Elasticity invariants (ISSUE 6 tentpole) — membership changes must not
weaken any of the above, and add contracts of their own:

  6. **Drains lose nothing**: every graceful drain evacuated ALL its
     sole-replica objects before terminating — an object that had a
     surviving replica (or time to gain one) is never lost to a drain.
  7. **Restart budgets hold**: no actor's ``num_restarts`` ever exceeds its
     ``max_restarts`` — drains, head restarts, and chaos kills all consume
     the same FSM budget.
  8. **Plan state machines are legal**: compiled plans only ever move
     READY→BROKEN (death), BROKEN→READY (repair), or →TORN_DOWN — audited
     from the cluster's transition log so released plans stay checkable.

Gray-failure invariants (ISSUE 8 tentpole) — partitions that heal must not
reintroduce the dead:

  9. **No commit lands from a fenced incarnation**: a node the fabric
     declared dead never re-enters the object directory (its locations
     stay purged), and every fence event on record names a node that is
     genuinely DEAD — fencing never false-positives a live node.
 10. **At most one terminal side-effect per task across a heal**: no
     terminal task event was recorded from a node a fence event rejected
     for that same task — the resubmitted attempt's result is the ONLY one
     visible.

Overload invariant (ISSUE 9 tentpole) — bounded admission queues must shed
correctly, never lose or duplicate work:

 11. **Sheds are typed and final**: every admitted request terminates
     exactly once — value or typed error (checks 2 and 3 applied to the
     merged workload + injector refs) — every shed request got the typed
     ``OverloadedError`` signal (audited from ``cluster.overload_events``,
     which only the typed-shed paths feed), and no shed task ever
     executed (a shed task id with a FINISHED terminal record is a
     shed-then-run double execution).

Training invariant (ISSUE 17 tentpole) — repair must be bit-exact, not
merely "it kept going":

 12. **Post-repair loss trajectory equals an uninterrupted run's**: every
     gang repair this run recorded (``cluster.train_repair_audits``)
     carries the restored checkpoint state and the losses the gang
     produced after resuming; replaying the same number of steps from the
     same state WITHOUT the gang (single-process, same seeded batches,
     same update arithmetic) must reproduce those losses byte-for-byte
     (float32 buffers compared with ``tobytes()``).  A repair that resumed
     from torn state, re-sharded batches non-deterministically, or summed
     gradients in a different order fails here even though training
     "continued" without error.

Disaggregated-serving invariant (ISSUE 20 tentpole) — KV-block migrations
must never leak or double-free staged state:

 13. **Every staged migration reaches exactly one terminal**: each
     ``"staged"`` audit row in ``cluster.kv_migration_audits``
     (serve/disagg.py) pairs with exactly one ``"released"`` row —
     outcome ``adopted``, ``reprefill``, or ``failed``.  Zero terminals
     means the prefill replica's staged block set leaked; more than one
     means it was freed twice.  A decode-replica kill mid-migration must
     still land here: the re-prefill ladder releases the orphaned attempt
     before staging the next.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


class InvariantReport:
    """Outcome of one invariant sweep; truthy iff everything held."""

    def __init__(self):
        self.violations: List[str] = []
        self.checked: Dict[str, Any] = {}

    @property
    def ok(self) -> bool:
        return not self.violations

    def __bool__(self) -> bool:
        return self.ok

    def add(self, message: str) -> None:
        self.violations.append(message)

    def to_dict(self) -> dict:
        return {"ok": self.ok, "violations": list(self.violations), "checked": dict(self.checked)}

    def __repr__(self):
        state = "ok" if self.ok else f"{len(self.violations)} violation(s)"
        return f"InvariantReport({state}: {self.violations})"


def snapshot_baseline() -> dict:
    """Capture the pre-run footprint the post-run state must return to.
    Call BEFORE submitting the chaos workload."""
    from ray_tpu.runtime.worker import global_worker

    worker = global_worker()
    worker.ref_counter.drain_deferred()
    cluster = worker.cluster
    return {
        "tracked_refs": worker.ref_counter.num_tracked(),
        "num_task_events": len(cluster.control.task_events),
        # elasticity scoping: only drains / plan transitions from THIS run
        "num_drain_reports": len(getattr(cluster, "drain_reports", ())),
        "num_plan_transitions": len(getattr(cluster, "plan_transitions", ())),
        "num_fence_events": getattr(cluster, "fence_events_total", 0),
        "num_overload_events": getattr(cluster, "overload_events_total", 0),
        "num_train_repairs": len(getattr(cluster, "train_repair_audits", ())),
        "num_kv_migration_audits": len(getattr(cluster, "kv_migration_audits", ())),
    }


def wait_quiescent(cluster, timeout: float = 60.0, settle_s: float = 0.2) -> bool:
    """Wait until no task is pending and the state holds for ``settle_s``
    (a retry landing between polls must not count as quiescent)."""
    deadline = time.monotonic() + timeout
    settled_since: Optional[float] = None
    while time.monotonic() < deadline:
        if cluster.task_manager.num_pending() == 0:
            if settled_since is None:
                settled_since = time.monotonic()
            elif time.monotonic() - settled_since >= settle_s:
                return True
        else:
            settled_since = None
        time.sleep(0.02)
    return False


_EXPECTED_ERRORS_CACHE = None


def _expected_errors() -> tuple:
    global _EXPECTED_ERRORS_CACHE
    if _EXPECTED_ERRORS_CACHE is None:
        from ray_tpu import exceptions as exc
        from ray_tpu.runtime.failpoints import FailpointInjected

        _EXPECTED_ERRORS_CACHE = (
            exc.RayTaskError,
            exc.RayActorError,
            exc.ObjectLostError,
            exc.WorkerCrashedError,
            exc.TaskCancelledError,
            exc.OverloadedError,
            exc.StoreFullError,
            exc.DeadlineExceededError,
            FailpointInjected,
        )
    return _EXPECTED_ERRORS_CACHE


def _lineage_pinned(cluster) -> set:
    """ObjectIDs held alive by retained lineage specs' top-level args —
    the designed pins check 4 must not count as leaks."""
    from ray_tpu.core.object_ref import ObjectRef

    with cluster.task_manager._lock:
        specs = {id(s): s for s in cluster.task_manager._lineage.values()}
    pinned = set()
    for spec in specs.values():
        values = list(getattr(spec, "args", ()) or ())
        values += list((getattr(spec, "kwargs", {}) or {}).values())
        for v in values:
            if isinstance(v, ObjectRef):
                pinned.add(v.id())
    return pinned


def check_invariants(
    refs: Optional[List[Any]] = None,
    baseline: Optional[dict] = None,
    timeout: float = 60.0,
) -> InvariantReport:
    """Run the full sweep against the current runtime.  ``refs`` are the
    workload's ObjectRefs (resolved, then dropped for the refcount check);
    ``baseline`` comes from :func:`snapshot_baseline`."""
    import ray_tpu as rt
    from ray_tpu.exceptions import GetTimeoutError, ObjectLostError
    from ray_tpu.observability.tracing import SPAN_EVENT_TYPE
    from ray_tpu.runtime.worker import global_worker

    worker = global_worker()
    cluster = worker.cluster
    report = InvariantReport()

    # 1. pending set drains -------------------------------------------------
    if not wait_quiescent(cluster, timeout=timeout):
        stuck = [s.name for s in cluster.task_manager.pending_specs()]
        report.add(f"tasks never quiesced: {len(stuck)} still pending ({stuck[:5]}...)")
    report.checked["pending_after"] = cluster.task_manager.num_pending()

    # 2. every ref resolves or raises a typed error -------------------------
    # Ownership note: the caller hands the ref list over — it is CLEARED
    # before the refcount check so the workload's pins actually drop.
    resolved = failed = 0
    ref_list = refs if isinstance(refs, list) else list(refs or [])
    deadline = time.monotonic() + timeout
    for ref in ref_list:
        remaining = max(0.5, deadline - time.monotonic())
        try:
            value = rt.get(ref, timeout=remaining)
        except GetTimeoutError:
            report.add(f"silent loss: {ref} neither resolved nor raised within {timeout}s")
            continue
        except _expected_errors():
            failed += 1
            continue
        except BaseException as exc:  # noqa: BLE001 — anything else is a contract break
            report.add(f"untyped failure from get({ref}): {type(exc).__name__}: {exc}")
            continue
        if isinstance(value, BaseException):
            # an error object RETURNED as data — the "lost value without a
            # raised ObjectLostError" failure mode, verbatim
            report.add(
                f"silent loss: get({ref}) returned {type(value).__name__} "
                "instead of raising it"
            )
            continue
        resolved += 1
    report.checked["refs_resolved"] = resolved
    report.checked["refs_failed_typed"] = failed

    # 3. terminal exactly once per (task_id, attempt) -----------------------
    events = cluster.control.task_events.list_events(limit=1_000_000)
    if baseline is not None:
        # scope to THIS run: events recorded before the baseline snapshot
        # belong to earlier workloads in the session
        events = events[baseline.get("num_task_events", 0):]
    terminal: Dict[tuple, int] = {}
    attempts_by_task: Dict[str, set] = {}
    for ev in events:
        if ev.get("state") in ("FINISHED", "FAILED"):
            key = (ev["task_id"], ev.get("attempt", 0))
            terminal[key] = terminal.get(key, 0) + 1
            attempts_by_task.setdefault(ev["task_id"], set()).add(ev.get("attempt", 0))
    dupes = {k: n for k, n in terminal.items() if n > 1}
    if dupes:
        report.add(f"non-unique terminal records for (task, attempt): {list(dupes)[:5]}")
    report.checked["terminal_records"] = len(terminal)

    # 4. refcounts return to baseline --------------------------------------
    # Lineage retention is a DESIGNED pin, not a leak: completed specs keep
    # their argument refs alive so lost returns can reconstruct (reference
    # lineage refcount parity, task_manager.h:261) — the baseline allows
    # for refs reachable through retained lineage specs.
    if baseline is not None:
        ref_list.clear()  # drop the workload's pins before measuring
        ref = value = None  # the loop locals pin the last ref otherwise
        # caught injected faults leave traceback<->frame cycles whose frames
        # pin the workload's ref lists; init defers cyclic GC, so collect
        # explicitly before calling anything a leak
        import gc

        gc.collect()
        worker.ref_counter.drain_deferred()
        allowed = baseline["tracked_refs"] + len(_lineage_pinned(cluster))
        # out-of-scope deletions ripple through callbacks; settle briefly
        settle_deadline = time.monotonic() + 5.0
        tracked = worker.ref_counter.num_tracked()
        while tracked > allowed and time.monotonic() < settle_deadline:
            time.sleep(0.05)
            worker.ref_counter.drain_deferred()
            tracked = worker.ref_counter.num_tracked()
            allowed = baseline["tracked_refs"] + len(_lineage_pinned(cluster))
        report.checked["tracked_refs"] = tracked
        report.checked["lineage_pinned"] = allowed - baseline["tracked_refs"]
        if tracked > allowed:
            report.add(
                f"refcount leak: {tracked} tracked refs after the run "
                f"(baseline {baseline['tracked_refs']} + "
                f"{allowed - baseline['tracked_refs']} lineage-pinned)"
            )

    # 5. retried attempts visible as distinct spans -------------------------
    spans = cluster.control.spans.list_events(limit=1_000_000)
    retry_attempts: Dict[str, set] = {}
    for ev in spans:
        if ev.get("type") == SPAN_EVENT_TYPE and str(ev.get("name", "")).startswith("retry::"):
            attrs = ev.get("attrs") or {}
            tid = attrs.get("task_id")
            if tid is not None:
                retry_attempts.setdefault(tid, set()).add(attrs.get("attempt"))
    for task_id, attempts in attempts_by_task.items():
        final_attempt = max(attempts)
        if final_attempt > 0:
            seen = retry_attempts.get(task_id, set())
            if len(seen) < final_attempt:
                report.add(
                    f"task {task_id[:8]} reached attempt {final_attempt} but only "
                    f"{len(seen)} retry spans are in the span store"
                )
    report.checked["tasks_with_retries"] = sum(1 for a in attempts_by_task.values() if max(a) > 0)

    # 6. drains lose nothing that had somewhere to go -----------------------
    drain_reports = list(getattr(cluster, "drain_reports", ()))
    if baseline is not None:
        drain_reports = drain_reports[baseline.get("num_drain_reports", 0):]
    for rep in drain_reports:
        if rep.get("failed_evacuations"):
            report.add(
                f"drain of node {rep['node']} terminated with "
                f"{rep['failed_evacuations']} sole-replica object(s) "
                "unevacuated (survivors existed)"
            )
    report.checked["drains"] = len(drain_reports)
    report.checked["drain_evacuated"] = sum(r.get("evacuated", 0) for r in drain_reports)

    # 7. actor restart budgets hold -----------------------------------------
    over_budget = [
        info for info in cluster.control.actors.list_actors()
        if info.max_restarts >= 0 and info.num_restarts > info.max_restarts
    ]
    for info in over_budget:
        report.add(
            f"actor {info.actor_id.hex()[:8]} restarted {info.num_restarts} "
            f"times with max_restarts={info.max_restarts}"
        )

    # 8. compiled-plan state machines are legal -----------------------------
    legal = {
        ("READY", "BROKEN"), ("BROKEN", "READY"),
        ("READY", "TORN_DOWN"), ("BROKEN", "TORN_DOWN"),
    }
    transitions = list(getattr(cluster, "plan_transitions", ()))
    if baseline is not None:
        transitions = transitions[baseline.get("num_plan_transitions", 0):]
    last_state: Dict[str, str] = {}
    for plan_id, src, dst in transitions:
        prev = last_state.get(plan_id, src)
        if (prev, dst) not in legal or prev != src:
            report.add(
                f"plan {plan_id[:8]} made an illegal state transition "
                f"{src}->{dst} (after {prev})"
            )
        last_state[plan_id] = dst
    report.checked["plan_transitions"] = len(transitions)

    # 9. no commit lands from a fenced incarnation --------------------------
    from ray_tpu.runtime.control import NodeState

    dead_nodes = {
        info.node_id
        for info in cluster.control.nodes.all_nodes()
        if info.state is NodeState.DEAD
    }
    dead_short = {nid.hex()[:8] for nid in dead_nodes}
    with cluster.directory._lock:
        for oid, locs in cluster.directory._locations.items():
            bad = locs & dead_nodes
            if bad:
                report.add(
                    f"fenced incarnation re-entered the directory: object "
                    f"{oid.hex()[:8]} located on dead node(s) "
                    f"{[n.hex()[:8] for n in bad]}"
                )
                break
    fence_events = list(getattr(cluster, "fence_events", ()))
    if baseline is not None:
        # the log is a bounded deque: slice THIS run's tail by the
        # monotonic total, not a list index
        delta = getattr(cluster, "fence_events_total", 0) - baseline.get(
            "num_fence_events", 0
        )
        fence_events = fence_events[-delta:] if delta > 0 else []
    for fe in fence_events:
        if fe.get("node") and fe["node"] not in dead_short:
            if (
                fe.get("incarnation") is not None
                and fe.get("current") is not None
                and fe["incarnation"] != fe["current"]
            ):
                # a stale EPOCH of a still-alive node id (transient rejoin
                # superseded the old connection): fencing working as
                # designed, not a false positive
                continue
            report.add(
                f"fence false-positive: frame from LIVE node {fe['node']} "
                f"rejected ({fe.get('kind')})"
            )
    report.checked["fence_events"] = len(fence_events)

    # 10. at most one terminal side-effect per task across a heal -----------
    fenced_tasks = {
        (fe.get("task"), fe.get("node"))
        for fe in fence_events
        if fe.get("task")
    }
    if fenced_tasks:
        for ev in events:
            if ev.get("state") in ("FINISHED", "FAILED") and (
                ev.get("task_id"), ev.get("node")
            ) in fenced_tasks:
                report.add(
                    f"fenced commit LANDED: task {ev['task_id'][:8]} has a "
                    f"terminal record from fenced node {ev['node']}"
                )
    report.checked["fenced_tasks"] = len(fenced_tasks)

    # 11. overload sheds are typed, attributed, and shed work never ran ------
    overload_events = list(getattr(cluster, "overload_events", ()))
    if baseline is not None:
        # bounded deque: slice THIS run's tail by the monotonic total
        delta = getattr(cluster, "overload_events_total", 0) - baseline.get(
            "num_overload_events", 0
        )
        overload_events = overload_events[-delta:] if delta > 0 else []
    finished_tasks = {
        ev.get("task_id") for ev in events if ev.get("state") == "FINISHED"
    }
    for oe in overload_events:
        if not oe.get("typed"):
            report.add(f"shed WITHOUT the typed signal: {oe}")
        if not oe.get("layer") or not oe.get("reason"):
            report.add(f"unattributed overload shed: {oe}")
        task = oe.get("task")
        if task and task in finished_tasks:
            report.add(
                f"shed task {task[:8]} has a FINISHED terminal record — "
                "shed-then-run double execution"
            )
    report.checked["overload_sheds"] = len(overload_events)

    # 12. post-repair loss trajectory equals an uninterrupted run's ---------
    audits = list(getattr(cluster, "train_repair_audits", ()))
    if baseline is not None:
        audits = audits[baseline.get("num_train_repairs", 0):]
    replayed_steps = 0
    for audit in audits:
        losses = list(audit.get("losses", ()))
        if not losses:
            continue  # repair landed but no post-repair step ran this run
        import numpy as np

        expected = audit["replay"](
            audit["state"], audit["world_size"], len(losses)
        )
        got = np.asarray(losses, np.float32).tobytes()
        want = np.asarray(expected, np.float32).tobytes()
        if got != want:
            report.add(
                f"train repair of {audit.get('controller')!r} at step "
                f"{audit.get('start_step')} ({audit.get('outcome')}) diverged "
                f"from the uninterrupted replay over {len(losses)} step(s)"
            )
        replayed_steps += len(losses)
    report.checked["train_repairs"] = len(audits)
    report.checked["train_replayed_steps"] = replayed_steps

    # 13. every staged KV-block migration reaches exactly one terminal ------
    # (serve/disagg.py: "staged" must pair with exactly one "released" —
    # adopted, reprefill, or failed; zero terminals leaks the staged set,
    # two would double-free it)
    mig_audits = list(getattr(cluster, "kv_migration_audits", ()))
    if baseline is not None:
        mig_audits = mig_audits[baseline.get("num_kv_migration_audits", 0):]
    staged_ids: List[str] = []
    released: Dict[str, int] = {}
    for audit in mig_audits:
        mid = audit.get("mig_id", "")
        if audit.get("event") == "staged":
            staged_ids.append(mid)
        elif audit.get("event") == "released":
            released[mid] = released.get(mid, 0) + 1
    for mid in staged_ids:
        n = released.get(mid, 0)
        if n == 0:
            report.add(
                f"kv migration {mid!r} staged but never released — the "
                "staged block set leaked"
            )
        elif n > 1:
            report.add(
                f"kv migration {mid!r} released {n} times — staged block "
                "set freed more than once"
            )
    for mid, n in released.items():
        if mid not in staged_ids:
            report.add(
                f"kv migration {mid!r} released without a staged record"
            )
    report.checked["kv_migrations"] = len(staged_ids)
    return report
