"""Chaos schedule runner: execute a workload while walking a fault timeline.

The runner owns the full life of one chaos run:

  1. reset the fault log and arm the schedule's t=0 state,
  2. snapshot the invariant baseline (refcounts, event counts),
  3. start the workload on its own thread and walk the timeline — arming /
     disarming failpoints, opening timed partitions, killing nodes through
     the existing ``cluster.kill_node`` hook, losing committed objects,
  4. join the workload, wait for quiescence, disarm everything the schedule
     armed (restoring whatever was armed before the run),
  5. run the invariant sweep and return a :class:`ChaosResult` carrying the
     deterministic fault log, the invariant report, and the workload's
     resolution summary.

The **workload** is a zero-arg callable.  If it returns a list of
``ObjectRef`` (the common shape: submit, return the refs), the runner
resolves them inside the invariant sweep; any other return value is kept
verbatim as ``result.workload_result``.

Determinism: ``result.faults`` is ``failpoints.fault_log()`` — sorted by
``(failpoint, hit)``, identical across runs of the same ``(seed, schedule,
workload)``.  ``ChaosResult.same_faults(other)`` is the comparison a
regression suite asserts.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional

from ray_tpu.chaos import invariants as _inv
from ray_tpu.chaos.schedule import ChaosEvent, ChaosSchedule
from ray_tpu.runtime import failpoints


class ChaosResult:
    def __init__(self):
        self.faults: List[dict] = []
        self.invariants: Optional[_inv.InvariantReport] = None
        self.workload_result: Any = None
        self.workload_error: Optional[BaseException] = None
        self.events_applied: List[dict] = []
        self.duration_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.workload_error is None and bool(self.invariants)

    def same_faults(self, other: "ChaosResult") -> bool:
        return self.faults == other.faults

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "duration_s": round(self.duration_s, 3),
            "faults": self.faults,
            "events_applied": self.events_applied,
            "invariants": self.invariants.to_dict() if self.invariants else None,
            "workload_error": repr(self.workload_error) if self.workload_error else None,
        }


class ChaosRunner:
    def __init__(self, schedule: ChaosSchedule, quiesce_timeout: float = 60.0):
        self.schedule = schedule
        self.quiesce_timeout = quiesce_timeout
        # refs minted by `overload` injector events: resolved by the
        # invariant sweep alongside the workload's refs, so every injected
        # request provably terminates exactly once (value or typed error)
        self._injected_refs: List[Any] = []

    # ------------------------------------------------------------------
    def run(self, workload: Callable[[], Any]) -> ChaosResult:
        from ray_tpu.core.object_ref import ObjectRef
        from ray_tpu.runtime.worker import global_worker

        cluster = global_worker().cluster
        result = ChaosResult()
        pre_spec = failpoints.armed_spec()  # restored after the run
        failpoints.disarm()                 # also clears log + hit counters
        if pre_spec:
            failpoints.arm(pre_spec, seed=self.schedule.seed)
        else:
            # fix the seed even with nothing armed yet: the first timeline
            # "arm" event must join an already-seeded decision stream
            failpoints.arm({}, seed=self.schedule.seed)
        baseline = _inv.snapshot_baseline()

        box: dict = {}

        def _run_workload():
            try:
                box["value"] = workload()
            except BaseException as exc:  # noqa: BLE001 — reported, not raised
                box["error"] = exc

        t_start = time.monotonic()
        restores: List[tuple] = []  # (deadline, fp name, previous entry|None)
        # t<=0 events apply BEFORE the workload starts: arming must never
        # race the first dispatches, or hit indices shift run-to-run and
        # the fault log stops being reproducible
        timed_events = []
        for event in self.schedule.events:
            if event.t <= 0.0:
                try:
                    applied = self._apply(cluster, event, restores, t_start)
                except Exception as exc:  # noqa: BLE001
                    applied = {"error": f"{type(exc).__name__}: {exc}"}
                result.events_applied.append({"t": event.t, "kind": event.kind, **(applied or {})})
            else:
                timed_events.append(event)
        worker_thread = threading.Thread(target=_run_workload, name="chaos-workload", daemon=True)
        worker_thread.start()

        # -- walk the timeline ------------------------------------------
        for event in timed_events:
            self._sleep_until(t_start + event.t)
            self._fire_pending_restores(restores, now=time.monotonic())
            try:
                applied = self._apply(cluster, event, restores, t_start)
            except Exception as exc:  # noqa: BLE001 — a bad event must not strand the run
                applied = {"error": f"{type(exc).__name__}: {exc}"}
            result.events_applied.append({"t": event.t, "kind": event.kind, **(applied or {})})
        # close any still-open partition windows
        while restores:
            self._sleep_until(min(r[0] for r in restores))
            self._fire_pending_restores(restores, now=time.monotonic())

        worker_thread.join(timeout=self.quiesce_timeout)
        if worker_thread.is_alive():
            result.workload_error = TimeoutError(
                f"chaos workload still running after {self.quiesce_timeout}s"
            )
        else:
            result.workload_error = box.get("error")
            result.workload_result = box.get("value")

        # -- capture the deterministic artifact, restore pre-run arming --
        # Quiesce FIRST: a workload that returns unresolved refs still has
        # tasks in flight, and disarming/capturing mid-flight would make
        # the log race-dependent (truncated at a wall-clock instant).
        _inv.wait_quiescent(cluster, timeout=self.quiesce_timeout)
        result.faults = failpoints.fault_log()
        failpoints.disarm()
        if pre_spec:
            failpoints.arm(pre_spec)

        # -- invariants --------------------------------------------------
        refs = None
        value = result.workload_result
        if isinstance(value, list) and value and all(isinstance(r, ObjectRef) for r in value):
            refs = value
            result.workload_result = f"<{len(refs)} refs (resolved by invariant sweep)>"
        if self._injected_refs:
            if refs is None:
                refs = self._injected_refs
            else:
                # extend IN PLACE: the sweep clears this list to drop the
                # workload's pins, and the workload's own reference to it
                # must drain too (a fresh merged list would leave the
                # original pinning every ref past the refcount check)
                refs.extend(self._injected_refs)
            self._injected_refs = []
        result.invariants = _inv.check_invariants(
            refs=refs, baseline=baseline, timeout=self.quiesce_timeout
        )
        result.duration_s = time.monotonic() - t_start
        return result

    # ------------------------------------------------------------------
    @staticmethod
    def _sleep_until(deadline: float) -> None:
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            time.sleep(min(remaining, 0.05))

    @staticmethod
    def _fire_pending_restores(restores: List[tuple], now: float) -> None:
        for entry in list(restores):
            deadline, name, prev = entry
            if now >= deadline:
                if prev is None:
                    failpoints.disarm(name)
                else:
                    failpoints.arm({name: prev})
                restores.remove(entry)

    def _apply(self, cluster, event: ChaosEvent, restores: List[tuple], t_start: float) -> dict:
        p = event.params
        if event.kind == "arm":
            failpoints.arm(p["spec"], seed=self.schedule.seed)
            return {"spec": p["spec"]}
        if event.kind == "disarm":
            failpoints.disarm(p.get("name"))
            return {"name": p.get("name")}
        if event.kind == "partition":
            name = p["fp"]
            prev = failpoints.configured(name)
            failpoints.arm({name: {"action": "partition", "prob": 1.0, "delay_s": 0.0}})
            restores.append((t_start + event.t + float(p.get("duration", 1.0)), name, prev))
            return {"fp": name, "duration": p.get("duration", 1.0)}
        if event.kind in ("kill_node", "drain_node"):
            victims = [
                (nid, node) for nid, node in cluster.nodes.items()
                if not node.dead and node is not cluster.head_node
            ]
            idx = int(p.get("index", 0))
            if idx >= len(victims):
                return {"skipped": f"no live non-head node at index {idx}"}
            nid, node = victims[idx]
            if event.kind == "kill_node":
                cluster.kill_node(nid, reason="chaos schedule kill_node")
                return {"node": nid.hex()[:8]}
            report = cluster.drain_node(nid, timeout_s=p.get("timeout"))
            return {
                "node": nid.hex()[:8],
                "outcome": report["outcome"],
                "evacuated": report["evacuated"],
                "actors_restarted": report["actors_restarted"],
            }
        if event.kind in ("slow_node", "partition_node"):
            victims = [
                (nid, node) for nid, node in cluster.nodes.items()
                if not node.dead and node is not cluster.head_node
            ]
            idx = int(p.get("index", 0))
            if idx >= len(victims):
                return {"skipped": f"no live non-head node at index {idx}"}
            nid, node = victims[idx]
            if event.kind == "slow_node":
                # deterministic straggler: a fixed per-dispatch delay — no
                # failpoint decisions consumed, fault logs unaffected
                node._chaos_delay_s = float(p.get("delay", 1.0))
                return {"node": nid.hex()[:8], "delay": node._chaos_delay_s}
            cluster.partition_node(nid)
            return {"node": nid.hex()[:8]}
        if event.kind == "heal_partition":
            fresh = cluster.heal_partition()
            if fresh is None:
                return {"skipped": "nothing partitioned"}
            return {"node": fresh.node_id.hex()[:8]}
        if event.kind == "add_node":
            node = cluster.add_node(
                dict(p.get("resources") or {"CPU": 1}), labels=p.get("labels")
            )
            return {"node": node.node_id.hex()[:8]}
        if event.kind == "kill_head":
            return {"snapshot": cluster.kill_head()}
        if event.kind == "restart_head":
            return cluster.restart_head()
        if event.kind == "lose_objects":
            return self._lose_objects(cluster, float(p.get("fraction", 0.5)))
        if event.kind == "overload":
            return self._inject_overload(
                int(p.get("tasks", 32)),
                float(p.get("cpus", 1.0)),
                float(p.get("hold_s", 0.0)),
            )
        if event.kind == "preempt_gang_member":
            return self._preempt_gang_member(
                cluster,
                p.get("job"),
                p.get("index"),
                bool(p.get("graceful", True)),
            )
        if event.kind == "kill_decode_replica":
            return self._kill_decode_replica(
                cluster,
                p.get("deployment"),
                str(p.get("role", "decode")),
                int(p.get("index", 0)),
            )
        return {}

    @staticmethod
    def _kill_decode_replica(cluster, deployment, role: str, index: int) -> dict:
        """Kill one replica of a disaggregated serving deployment through
        the controller's chaos hook.  Like preempt_gang_member this
        consumes NO failpoint decisions — same-seed fault logs stay
        byte-identical; what it perturbs is the replica pool.  A migration
        in flight must walk the re-prefill ladder (typed KVMigrationError
        internally), and invariant 13 audits that every staged block set
        still reached exactly one terminal outcome."""
        controllers = getattr(cluster, "serve_controllers", {})
        if not controllers:
            return {"skipped": "no registered serve controllers"}
        for key in sorted(controllers):
            ctl = controllers[key]
            try:
                killed = ctl.chaos_kill_replica(
                    deployment or "", role=role, index=index
                )
            except Exception as exc:  # noqa: BLE001 — report, don't crash
                return {"skipped": f"controller hook failed: {exc!r}"}
            if killed:
                return {
                    "deployment": deployment or "(sole roles deployment)",
                    "role": role,
                    "index": index,
                }
        return {"skipped": f"no {role!r} replica at index {index}"}

    @staticmethod
    def _preempt_gang_member(cluster, job, index, graceful: bool) -> dict:
        """Preempt one member of a registered training gang.  Like the
        overload injector this consumes no failpoint decisions, so
        same-seed fault logs stay byte-identical; what it perturbs is the
        gang itself.  ``graceful=True`` exercises the serving-burst ladder
        (checkpoint → shrink → continue); ``graceful=False`` hard-kills the
        member, and the workload's repair must resume bit-exact from the
        latest step checkpoint (invariant 12 audits the resumed loss
        trajectory against an uninterrupted replay)."""
        controllers = getattr(cluster, "train_controllers", {})
        if job is None:
            names = sorted(controllers)
            if not names:
                return {"skipped": "no registered training gangs"}
            job = names[0]
        ctl = controllers.get(job)
        if ctl is None:
            return {"skipped": f"no training gang named {job!r}"}
        if graceful:
            new_size = ctl.preempt_member(index, graceful=True)
            return {"job": job, "graceful": True, "gang_size": new_size}
        ctl.preempt_member(index, graceful=False)
        return {"job": job, "graceful": False, "killed_index": index}

    def _inject_overload(self, tasks: int, cpus: float, hold_s: float) -> dict:
        """Deterministic synthetic load burst: ``tasks`` submissions each
        demanding ``cpus`` CPUs and holding them ``hold_s`` seconds.  No
        failpoint decisions are consumed, so same-seed fault logs stay
        byte-identical; what varies under overload is WHICH admission layer
        sheds, and invariant 11 audits that every shed was typed and no
        shed task executed.  Refs (including ones whose terminal state is
        the committed OverloadedError) join the invariant sweep."""
        import ray_tpu as rt
        from ray_tpu.exceptions import OverloadedError

        @rt.remote(num_cpus=cpus, max_retries=0)
        def _overload_probe(i, hold):
            if hold:
                time.sleep(hold)
            return i

        admitted = shed_at_submit = 0
        for i in range(tasks):
            try:
                self._injected_refs.append(_overload_probe.remote(i, hold_s))
                admitted += 1
            except OverloadedError:
                # submission-layer shed: typed, raised before a ref was
                # minted (queue-layer sheds commit the error to the ref
                # instead, and resolve in the sweep)
                shed_at_submit += 1
        return {"tasks": tasks, "submitted": admitted, "shed_at_submit": shed_at_submit}

    def _lose_objects(self, cluster, fraction: float) -> dict:
        """Delete a seeded fraction of committed objects from every store,
        forget their locations, and kick lineage reconstruction — recovery
        must rebuild them (or tombstone ObjectLostError) for the invariant
        sweep to pass."""
        with cluster.directory._lock:
            oids = sorted(cluster.directory._locations.keys(), key=lambda o: o.binary())
        lost = []
        for i, oid in enumerate(oids):
            if failpoints._decision(self.schedule.seed, "chaos.lose_objects", i) >= fraction:
                continue
            for node in list(cluster.nodes.values()):
                if not node.dead and hasattr(node, "store"):
                    try:
                        node.store.delete(oid)
                    except Exception:  # noqa: BLE001 — remote store already gone
                        pass
            cluster.directory.forget(oid)
            lost.append(oid)
        for oid in lost:
            cluster._try_recover(oid)
        return {"lost": len(lost), "of": len(oids)}


# --------------------------------------------------------------------------
# CLI entry (`rt chaos run`)
# --------------------------------------------------------------------------
def builtin_workload(name: str, rt):
    """Small self-contained workloads for `rt chaos run` demos/smokes."""
    if name == "fanout":
        def fanout():
            @rt.remote(max_retries=5)
            def bump(x):
                return x + 1

            return [bump.remote(i) for i in range(50)]

        return fanout
    if name == "actor":
        def actor():
            @rt.remote
            class Counter:
                def __init__(self):
                    self.n = 0

                def add(self, k):
                    self.n += k
                    return self.n

            c = Counter.options(max_task_retries=5, max_restarts=2).remote()
            return [c.add.remote(1) for _ in range(20)]

        return actor
    raise ValueError(f"unknown builtin chaos workload {name!r} (fanout|actor)")


def run_cli(args) -> int:
    """`rt chaos run --seed N --schedule f.json [--workload fanout]`."""
    import json

    import ray_tpu as rt

    # schema-check before burning minutes: a typo'd kind or malformed spec
    # fails in milliseconds with a friendly message, not mid-run
    from ray_tpu.chaos.schedule import validate_schedule

    with open(args.schedule) as f:
        errors = validate_schedule(json.load(f))
    if errors:
        import sys

        print(f"{args.schedule}: invalid schedule", file=sys.stderr)
        for err in errors:
            print(f"  - {err}", file=sys.stderr)
        return 1

    schedule = ChaosSchedule.load(args.schedule, seed=args.seed)
    own_runtime = not rt.is_initialized()
    if own_runtime:
        rt.init(num_cpus=args.num_cpus)
    try:
        runner = ChaosRunner(schedule, quiesce_timeout=args.timeout)
        result = runner.run(builtin_workload(args.workload, rt))
    finally:
        if own_runtime:
            rt.shutdown()
    print(json.dumps(result.to_dict(), indent=2))
    return 0 if result.ok else 1
