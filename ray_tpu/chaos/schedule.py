"""Declarative chaos schedules: a seeded timeline of fault events.

A schedule is the reproducible half of a chaos run — ``(seed, schedule)``
fully determines which faults fire (see the determinism contract in
``runtime/failpoints.py``).  Schedules are plain data, JSON round-trippable,
so a failing run is shipped and replayed as a file::

    {
      "seed": 42,
      "events": [
        {"t": 0.0, "kind": "arm",       "spec": "data_plane.send_frame=drop(0.2)"},
        {"t": 1.0, "kind": "partition", "fp": "agent.heartbeat", "duration": 3.0},
        {"t": 2.0, "kind": "kill_node", "index": 1},
        {"t": 2.5, "kind": "lose_objects", "fraction": 0.5},
        {"t": 3.0, "kind": "disarm"}
      ]
    }

Event kinds
-----------
``arm``           arm failpoints from ``spec`` (merges; see failpoints.arm).
``disarm``        disarm ``name`` (one failpoint) or everything.
``partition``     arm ``fp`` at probability 1.0 for ``duration`` seconds,
                  then restore whatever was armed before — a timed network
                  partition of that site.
``kill_node``     kill the ``index``-th live non-head node through the
                  existing ``cluster.kill_node`` chaos hook
                  (NodeKillerActor parity).
``lose_objects``  delete a seeded ``fraction`` of committed objects from
                  every store and kick lineage reconstruction — the
                  "silent storage loss" failure mode.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

_KINDS = ("arm", "disarm", "partition", "kill_node", "lose_objects")


class ChaosEvent:
    __slots__ = ("t", "kind", "params")

    def __init__(self, t: float, kind: str, **params: Any):
        if kind not in _KINDS:
            raise ValueError(f"unknown chaos event kind {kind!r} (expected one of {_KINDS})")
        self.t = float(t)
        self.kind = kind
        self.params = params

    def to_dict(self) -> dict:
        return {"t": self.t, "kind": self.kind, **self.params}

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosEvent":
        d = dict(d)
        t = d.pop("t", 0.0)
        kind = d.pop("kind")
        return cls(t, kind, **d)

    def __repr__(self):
        return f"ChaosEvent(t={self.t}, kind={self.kind!r}, {self.params})"


class ChaosSchedule:
    """An ordered fault timeline plus the decision-stream seed."""

    def __init__(self, events: List[ChaosEvent], seed: int = 0, name: str = ""):
        self.events = sorted(events, key=lambda e: e.t)
        self.seed = int(seed)
        self.name = name

    # ------------------------------------------------------------- codec
    def to_dict(self) -> dict:
        out: Dict[str, Any] = {"seed": self.seed, "events": [e.to_dict() for e in self.events]}
        if self.name:
            out["name"] = self.name
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosSchedule":
        return cls(
            [ChaosEvent.from_dict(e) for e in d.get("events", [])],
            seed=d.get("seed", 0),
            name=d.get("name", ""),
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "ChaosSchedule":
        return cls.from_dict(json.loads(text))

    @classmethod
    def load(cls, path: str, seed: Optional[int] = None) -> "ChaosSchedule":
        with open(path) as f:
            sched = cls.from_json(f.read())
        if seed is not None:
            sched.seed = int(seed)
        return sched

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    def duration(self) -> float:
        """Timeline span including partition windows (the runner keeps
        walking until every timed window has closed)."""
        end = 0.0
        for e in self.events:
            end = max(end, e.t + float(e.params.get("duration", 0.0)))
        return end
