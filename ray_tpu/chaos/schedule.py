"""Declarative chaos schedules: a seeded timeline of fault events.

A schedule is the reproducible half of a chaos run — ``(seed, schedule)``
fully determines which faults fire (see the determinism contract in
``runtime/failpoints.py``).  Schedules are plain data, JSON round-trippable,
so a failing run is shipped and replayed as a file::

    {
      "seed": 42,
      "events": [
        {"t": 0.0, "kind": "arm",       "spec": "data_plane.send_frame=drop(0.2)"},
        {"t": 1.0, "kind": "partition", "fp": "agent.heartbeat", "duration": 3.0},
        {"t": 2.0, "kind": "kill_node", "index": 1},
        {"t": 2.5, "kind": "lose_objects", "fraction": 0.5},
        {"t": 3.0, "kind": "disarm"}
      ]
    }

Event kinds
-----------
``arm``           arm failpoints from ``spec`` (merges; see failpoints.arm).
``disarm``        disarm ``name`` (one failpoint) or everything.
``partition``     arm ``fp`` at probability 1.0 for ``duration`` seconds,
                  then restore whatever was armed before — a timed network
                  partition of that site.
``kill_node``     kill the ``index``-th live non-head node through the
                  existing ``cluster.kill_node`` chaos hook
                  (NodeKillerActor parity).
``lose_objects``  delete a seeded ``fraction`` of committed objects from
                  every store and kick lineage reconstruction — the
                  "silent storage loss" failure mode.
``add_node``      grow the cluster mid-run: add a node with ``resources``
                  (default ``{"CPU": 1}``) and optional ``labels`` — the
                  elastic half of a scale event.
``drain_node``    gracefully remove the ``index``-th live non-head node via
                  ``cluster.drain_node`` (DrainRaylet parity): placements
                  stop, sole-replica objects evacuate, actors restart
                  elsewhere, then the node terminates.
``kill_head``     simulate head control-service death: durable state (incl.
                  failpoint hit counters) snapshots, then mutations go to
                  the doomed incarnation until ``restart_head``.
``restart_head``  restore the head from the kill-time snapshot; live nodes
                  re-adopt and live actor instances reconcile.
``slow_node``     arm a fixed per-dispatch delay on the ``index``-th live
                  non-head node (``delay`` seconds; 0 clears) — the
                  deterministic straggler the hedging machinery exists for.
``partition_node``  gray failure: declare the ``index``-th live non-head
                  node dead (full death sweep) WITHOUT shutting it down —
                  its runtime keeps executing and its commits must all be
                  rejected as fenced (stale incarnation).
``heal_partition``  the partition heals: the fenced node self-fences
                  (workers killed, store dropped, pins cleared) and a
                  FRESH node joins through the add_node elasticity path.
``overload``      deterministic synthetic load injector: submit ``tasks``
                  no-op tasks demanding ``cpus`` CPUs each and holding
                  their slot ``hold_s`` seconds — offered load beyond the
                  bounded admission queues must SHED with typed
                  OverloadedError, never grow a queue or double-execute
                  (invariant 11).  The injector's refs join the invariant
                  sweep's resolution set.
``preempt_gang_member``  preempt one member of a registered training gang
                  (``job`` names the TrainController; default: the first
                  registered, sorted).  ``graceful=True`` (default) drives
                  the checkpoint → shrink → continue ladder the serving
                  admission path uses; ``graceful=False`` hard-kills the
                  member (``kill -9`` equivalent), which must flip the plan
                  BROKEN with a typed error and repair bit-exact from the
                  latest step checkpoint (invariant 12).
``kill_decode_replica``  kill one replica of a disaggregated serving
                  deployment (``deployment`` names it; default: the sole
                  roles deployment), by ``role`` (default ``"decode"``) and
                  ``index`` within the pool (default 0, list order — never
                  random).  A migration in flight must surface as a typed
                  KVMigrationError internally and re-prefill on a fresh
                  replica pair; every staged block set still reaches
                  exactly one terminal outcome (invariant 13).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

_KINDS = (
    "arm", "disarm", "partition", "kill_node", "lose_objects",
    "add_node", "drain_node", "kill_head", "restart_head",
    "slow_node", "partition_node", "heal_partition", "overload",
    "preempt_gang_member", "kill_decode_replica",
)


class ChaosEvent:
    __slots__ = ("t", "kind", "params")

    def __init__(self, t: float, kind: str, **params: Any):
        if kind not in _KINDS:
            raise ValueError(f"unknown chaos event kind {kind!r} (expected one of {_KINDS})")
        self.t = float(t)
        self.kind = kind
        self.params = params

    def to_dict(self) -> dict:
        return {"t": self.t, "kind": self.kind, **self.params}

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosEvent":
        d = dict(d)
        t = d.pop("t", 0.0)
        kind = d.pop("kind")
        return cls(t, kind, **d)

    def __repr__(self):
        return f"ChaosEvent(t={self.t}, kind={self.kind!r}, {self.params})"


class ChaosSchedule:
    """An ordered fault timeline plus the decision-stream seed."""

    def __init__(self, events: List[ChaosEvent], seed: int = 0, name: str = ""):
        self.events = sorted(events, key=lambda e: e.t)
        self.seed = int(seed)
        self.name = name

    # ------------------------------------------------------------- codec
    def to_dict(self) -> dict:
        out: Dict[str, Any] = {"seed": self.seed, "events": [e.to_dict() for e in self.events]}
        if self.name:
            out["name"] = self.name
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosSchedule":
        return cls(
            [ChaosEvent.from_dict(e) for e in d.get("events", [])],
            seed=d.get("seed", 0),
            name=d.get("name", ""),
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "ChaosSchedule":
        return cls.from_dict(json.loads(text))

    @classmethod
    def load(cls, path: str, seed: Optional[int] = None) -> "ChaosSchedule":
        with open(path) as f:
            sched = cls.from_json(f.read())
        if seed is not None:
            sched.seed = int(seed)
        return sched

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    def duration(self) -> float:
        """Timeline span including partition windows (the runner keeps
        walking until every timed window has closed)."""
        end = 0.0
        for e in self.events:
            end = max(end, e.t + float(e.params.get("duration", 0.0)))
        return end


# --------------------------------------------------------------------------
# schema validation (`rt chaos validate`) — catch a malformed schedule in
# milliseconds instead of finding out minutes into a chaos run
# --------------------------------------------------------------------------

#: per-kind parameter schema: name -> (required, {param: allowed types})
_EVENT_SCHEMA: Dict[str, Dict[str, tuple]] = {
    "arm": {"spec": (True, (str, dict))},
    "disarm": {"name": (False, (str,))},
    "partition": {"fp": (True, (str,)), "duration": (False, (int, float))},
    "kill_node": {"index": (False, (int,))},
    "drain_node": {"index": (False, (int,)), "timeout": (False, (int, float))},
    "add_node": {"resources": (False, (dict,)), "labels": (False, (dict,))},
    "kill_head": {},
    "restart_head": {},
    "lose_objects": {"fraction": (False, (int, float))},
    "slow_node": {"index": (False, (int,)), "delay": (False, (int, float))},
    "partition_node": {"index": (False, (int,))},
    "heal_partition": {},
    "overload": {
        "tasks": (False, (int,)),
        "cpus": (False, (int, float)),
        "hold_s": (False, (int, float)),
    },
    "preempt_gang_member": {
        "job": (False, (str,)),
        "index": (False, (int,)),
        "graceful": (False, (bool,)),
    },
    "kill_decode_replica": {
        "deployment": (False, (str,)),
        "role": (False, (str,)),
        "index": (False, (int,)),
    },
}


def validate_schedule(data: Any, num_nodes: Optional[int] = None) -> List[str]:
    """Schema-check a schedule dict (as loaded from JSON) WITHOUT running
    anything.  Returns a list of friendly error strings — empty means valid.

    ``num_nodes`` (optional) is the number of live non-head worker nodes the
    run will start with; when given, ``kill_node``/``drain_node`` indices
    are bounds-checked against a simulated node count that tracks
    ``add_node``/``kill_node``/``drain_node`` events in timeline order."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return [f"schedule must be a JSON object, got {type(data).__name__}"]
    if "seed" in data and not isinstance(data["seed"], int):
        errors.append(f"'seed' must be an integer, got {data['seed']!r}")
    events = data.get("events")
    if events is None:
        return errors + ["schedule has no 'events' list"]
    if not isinstance(events, list):
        return errors + [f"'events' must be a list, got {type(events).__name__}"]

    from ray_tpu.runtime.failpoints import parse_spec

    indexed = []
    for i, ev in enumerate(events):
        where = f"event[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: must be an object, got {type(ev).__name__}")
            continue
        kind = ev.get("kind")
        if kind is None:
            errors.append(f"{where}: missing 'kind'")
            continue
        if kind not in _KINDS:
            errors.append(
                f"{where}: unknown kind {kind!r} (expected one of {', '.join(_KINDS)})"
            )
            continue
        t = ev.get("t", 0.0)
        if not isinstance(t, (int, float)) or isinstance(t, bool):
            errors.append(f"{where} ({kind}): 't' must be a number, got {t!r}")
            t = 0.0
        elif t < 0:
            errors.append(f"{where} ({kind}): 't' must be >= 0, got {t}")
        schema = _EVENT_SCHEMA[kind]
        for pname, (required, _types) in schema.items():
            if required and pname not in ev:
                errors.append(f"{where} ({kind}): missing required parameter {pname!r}")
        for pname, pval in ev.items():
            if pname in ("t", "kind"):
                continue
            if pname not in schema:
                errors.append(
                    f"{where} ({kind}): unknown parameter {pname!r} "
                    f"(accepts: {', '.join(schema) or 'none'})"
                )
                continue
            types = schema[pname][1]
            if not isinstance(pval, types) or (isinstance(pval, bool) and bool not in types):
                names = "/".join(tp.__name__ for tp in types)
                errors.append(
                    f"{where} ({kind}): {pname!r} must be {names}, got {pval!r}"
                )
        if kind == "arm" and isinstance(ev.get("spec"), str):
            try:
                parse_spec(ev["spec"])
            except ValueError as exc:
                errors.append(f"{where} (arm): bad failpoint spec: {exc}")
        if kind == "partition" and isinstance(ev.get("duration"), (int, float)) \
                and ev["duration"] <= 0:
            errors.append(f"{where} (partition): 'duration' must be > 0")
        if kind == "lose_objects" and isinstance(ev.get("fraction"), (int, float)) \
                and not 0.0 <= ev["fraction"] <= 1.0:
            errors.append(
                f"{where} (lose_objects): 'fraction' must be in [0, 1], "
                f"got {ev['fraction']}"
            )
        if kind in ("kill_node", "drain_node", "slow_node", "partition_node") \
                and isinstance(ev.get("index"), int) and ev["index"] < 0:
            errors.append(f"{where} ({kind}): 'index' must be >= 0")
        if kind == "slow_node" and isinstance(ev.get("delay"), (int, float)) \
                and ev["delay"] < 0:
            errors.append(f"{where} (slow_node): 'delay' must be >= 0")
        if kind == "preempt_gang_member" and isinstance(ev.get("index"), int) \
                and ev["index"] < 0:
            errors.append(f"{where} (preempt_gang_member): 'index' must be >= 0")
        if kind == "kill_decode_replica":
            if isinstance(ev.get("index"), int) and ev["index"] < 0:
                errors.append(f"{where} (kill_decode_replica): 'index' must be >= 0")
            if isinstance(ev.get("role"), str) and ev["role"] not in ("prefill", "decode"):
                errors.append(
                    f"{where} (kill_decode_replica): 'role' must be "
                    f"'prefill' or 'decode', got {ev['role']!r}"
                )
        if kind == "overload":
            if isinstance(ev.get("tasks"), int) and ev["tasks"] < 1:
                errors.append(f"{where} (overload): 'tasks' must be >= 1")
            if isinstance(ev.get("cpus"), (int, float)) and ev["cpus"] <= 0:
                errors.append(f"{where} (overload): 'cpus' must be > 0")
            if isinstance(ev.get("hold_s"), (int, float)) and ev["hold_s"] < 0:
                errors.append(f"{where} (overload): 'hold_s' must be >= 0")
        indexed.append((t, i, kind, ev))

    # timeline-order simulation: head liveness pairing + node-index bounds
    indexed.sort(key=lambda e: (e[0], e[1]))
    head_down = False
    live = num_nodes
    partitioned = 0
    for t, i, kind, ev in indexed:
        where = f"event[{i}]"
        if kind == "kill_head":
            if head_down:
                errors.append(f"{where}: kill_head while the head is already down")
            head_down = True
        elif kind == "restart_head":
            if not head_down:
                errors.append(f"{where}: restart_head without a preceding kill_head")
            head_down = False
        elif kind == "heal_partition":
            if partitioned <= 0:
                errors.append(
                    f"{where}: heal_partition without a preceding partition_node"
                )
            else:
                partitioned -= 1
                if live is not None:
                    live += 1  # the fenced node rejoins as a FRESH node
        elif live is not None:
            if kind == "add_node":
                live += 1
            elif kind in ("kill_node", "drain_node", "partition_node", "slow_node"):
                idx = ev.get("index", 0)
                if isinstance(idx, int) and idx >= live:
                    errors.append(
                        f"{where} ({kind}): index {idx} out of range — only "
                        f"{live} live non-head node(s) at t={t}"
                    )
                if kind != "slow_node":
                    live = max(0, live - 1)
                if kind == "partition_node":
                    partitioned += 1
        elif kind == "partition_node":
            partitioned += 1
    if head_down:
        errors.append("schedule ends with the head still down (missing restart_head)")
    return errors


def validate_cli(args) -> int:
    """``rt chaos validate <schedule.json> [--nodes N]``: schema-check a
    schedule before a run burns minutes on it."""
    import sys

    try:
        with open(args.schedule) as f:
            data = json.load(f)
    except OSError as exc:
        print(f"cannot read {args.schedule}: {exc}", file=sys.stderr)
        return 1
    except json.JSONDecodeError as exc:
        print(f"{args.schedule} is not valid JSON: {exc}", file=sys.stderr)
        return 1
    errors = validate_schedule(data, num_nodes=args.nodes)
    if errors:
        print(f"{args.schedule}: {len(errors)} problem(s)", file=sys.stderr)
        for err in errors:
            print(f"  - {err}", file=sys.stderr)
        return 1
    n = len(data.get("events", []))
    print(f"{args.schedule}: ok ({n} events, seed {data.get('seed', 0)})")
    return 0
