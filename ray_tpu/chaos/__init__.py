"""Deterministic chaos engineering for the ray_tpu fabric.

Three layers:

  * :mod:`ray_tpu.runtime.failpoints` — named fault-injection sites
    compiled into the runtime's hot paths (near-zero cost disarmed), with a
    seeded, hash-indexed decision stream: same ``(seed, spec, workload)``
    -> byte-for-byte identical fault log.
  * :mod:`ray_tpu.chaos.schedule` — a declarative fault timeline (arm a
    frame-drop at t=0, partition the heartbeat at t=1 for 3s, kill a node
    at t=2, lose half the committed objects at t=2.5), JSON-serializable
    so a failing chaos run ships as ``(seed, schedule.json)``.
  * :mod:`ray_tpu.chaos.runner` + :mod:`ray_tpu.chaos.invariants` — execute
    a workload while walking the timeline, wait for quiescence, then assert
    the recovery invariants: every submitted task reached a terminal state
    exactly once per attempt, no object ref resolves to a lost value
    without a raised ``ObjectLostError``, reference counts return to
    baseline, and every retried attempt is visible as a distinct span.

CLI: ``rt chaos run --seed N --schedule f.json``.
"""

from ray_tpu.chaos.invariants import InvariantReport, check_invariants, snapshot_baseline
from ray_tpu.chaos.runner import ChaosResult, ChaosRunner
from ray_tpu.chaos.schedule import ChaosEvent, ChaosSchedule, validate_schedule

__all__ = [
    "ChaosEvent",
    "ChaosSchedule",
    "ChaosRunner",
    "ChaosResult",
    "InvariantReport",
    "check_invariants",
    "snapshot_baseline",
    "validate_schedule",
]
